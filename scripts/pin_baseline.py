#!/usr/bin/env python
"""Measure the torch-CPU reference baseline ONCE, under a pinned
protocol, and persist it with provenance (VERDICT r4 weak #5 / next
#6: the bench's live denominator moved +-35-40% between runs of
identical code on this single-core host, dragging the headline
vs_baseline with it).

Protocol (recorded in the artifact):
  - model/params: the committed ML-1M cal2 MF checkpoint (the same
    config bench.py trains: k=16, wd 1e-3, 15k steps, cal2 stream) —
    reference solver settings avextol 1e-3 / maxiter 100
    (/root/reference/src/scripts/RQ1.py:19-20, its real speed).
  - queries: the first 64 of bench.py's own seed-17 test-split
    selection, so the pinned and live denominators sample the same
    workload distribution.
  - timing: best-of-5 wall per query (the host has ONE core; ambient
    load inflates single samples), summed over queries; per-query
    bests are stored so later rounds can re-validate the distribution
    instead of re-measuring.
  - torch threads pinned to 1 (explicit even though nproc=1, so the
    artifact stays valid if a future image adds cores).

bench.py reads output/pinned_baseline.json and reports vs_baseline
against the pinned number (stable across chip/tunnel state), plus
vs_baseline_live from its in-run sample for drift detection.

``--protocol bench`` (r6): when the reference checkout (data +
committed checkpoint) is not mounted, reproduce bench.py's own
synthetic-fallback workload in-process instead — the SAME shapes,
seeds, training config and seed-17 heldout query selection bench.py
uses when FIA_DATA_DIR is absent — so the pinned denominator and the
live in-run sample measure the identical workload. The protocol is
recorded in provenance; a pin and a live sample from different
protocols is exactly the drift the [0.67, 1.5] alert in bench.py
exists to catch.

Usage: python scripts/pin_baseline.py [--queries 64] [--reps 5]
       [--protocol reference|bench] [--out output/pinned_baseline.json]
"""

import argparse
import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from fia_tpu.utils.io import save_json_atomic  # noqa: E402


def main():
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--queries", type=int, default=64)
    ap.add_argument("--reps", type=int, default=5)
    ap.add_argument("--data_dir", default="/root/reference/data")
    ap.add_argument("--checkpoint", default=os.path.join(
        "output", "movielens_MF_explicit_damping1e-06_avextol1e-03_"
        "embed16_maxinf1_wd1e-03_cal2-checkpoint-14999.npz"))
    ap.add_argument("--out", default=os.path.join(
        "output", "pinned_baseline.json"))
    ap.add_argument("--protocol", choices=["reference", "bench"],
                    default="reference",
                    help="'reference': committed ML-1M checkpoint + "
                         "mounted reference data; 'bench': reproduce "
                         "bench.py's synthetic-fallback workload "
                         "in-process (no reference checkout needed)")
    args = ap.parse_args()

    import torch

    torch.set_num_threads(1)
    # jax is only used to unflatten the checkpoint pytree; keep it off
    # the (single-occupancy) TPU. The image's sitecustomize forces
    # platform=axon, so re-apply after import too.
    os.environ["JAX_PLATFORMS"] = "cpu"
    import jax

    jax.config.update("jax_platforms", "cpu")

    from fia_tpu.backends.torch_ref import TorchRefMFEngine
    from fia_tpu.models import MF

    users, items, k, wd = 6040, 3706, 16, 1e-3
    if args.protocol == "bench":
        # bench.py's synthetic fallback, shape for shape: zipf stream
        # seed 0, 15k training steps at lr 1e-3 / batch 3020, queries
        # from sample_heldout_pairs seed 17 — the exact arrays bench.py
        # builds when FIA_DATA_DIR is absent, so the pinned torch
        # denominator times the same model and the same query blocks
        # the live in-run sample does.
        from fia_tpu.data.synthetic import (
            sample_heldout_pairs,
            synthesize_ratings,
        )
        from fia_tpu.train.trainer import Trainer, TrainConfig

        rows, steps = 975_460, 15_000
        print(f"[{time.strftime('%H:%M:%S')}] bench protocol: training "
              f"{steps} steps on {rows} synthetic rows",
              file=sys.stderr, flush=True)
        train = synthesize_ratings(users, items, rows, seed=0)
        model = MF(users, items, k, wd)
        tr = Trainer(model, TrainConfig(batch_size=3020, num_steps=steps,
                                        learning_rate=1e-3))
        state = tr.fit(tr.init_state(model.init_params(
            jax.random.PRNGKey(0))), train.x, train.y)
        params = {kk: np.asarray(v) for kk, v in state.params.items()}
        points = sample_heldout_pairs(train.x, users, items, 256,
                                      seed=17)[: args.queries]
        checkpoint_name = f"in-process bench-protocol train ({steps} steps)"
        stream = "zipf"
    else:
        from fia_tpu.data.loaders import load_dataset
        from fia_tpu.train import checkpoint

        splits = load_dataset("movielens", args.data_dir)
        train = splits["train"]
        model = MF(users, items, k, wd)
        template = model.init_params(jax.random.PRNGKey(0))
        params, _, _ = checkpoint.load(args.checkpoint, template)
        params = {kk: np.asarray(v) for kk, v in params.items()}

        # bench.py's exact query selection (seed 17 over the test split)
        rng = np.random.default_rng(17)
        sel = rng.choice(splits["test"].num_examples, 256, replace=False)
        points = splits["test"].x[sel][: args.queries]
        checkpoint_name = os.path.basename(args.checkpoint)
        stream = getattr(train, "synth_tag", "") or "real"

    wd, damping = 1e-3, 1e-6
    ref = TorchRefMFEngine(params, train.x, train.y, weight_decay=wd,
                           damping=damping)

    load_before = os.getloadavg()
    t_start = time.time()
    per_query = []
    total_scores = 0
    for t, (u, i) in enumerate(points):
        reps = []
        for _ in range(args.reps):
            t0 = time.perf_counter()
            scores, rows = ref.query(int(u), int(i))
            reps.append(time.perf_counter() - t0)
        per_query.append({"u": int(u), "i": int(i), "rows": len(rows),
                          "best_s": round(min(reps), 5),
                          "all_s": [round(r, 5) for r in reps]})
        total_scores += len(rows)
        if (t + 1) % 8 == 0:
            print(f"[{time.strftime('%H:%M:%S')}] {t + 1}/{len(points)} "
                  "queries", file=sys.stderr, flush=True)

    total_time = sum(q["best_s"] for q in per_query)
    out = {
        "mf": {
            "scores_per_sec": round(total_scores / total_time, 1),
            "queries": len(points),
            "scores": total_scores,
            "best_of": args.reps,
            "median_query_s": round(
                float(np.median([q["best_s"] for q in per_query])), 5),
            "per_query": per_query,
        },
        "provenance": {
            "measured_at": time.strftime("%Y-%m-%dT%H:%M:%SZ",
                                         time.gmtime()),
            "wall_s": round(time.time() - t_start, 1),
            "torch_version": torch.__version__,
            "torch_threads": 1,
            "cpu_count": os.cpu_count(),
            "loadavg_before": load_before,
            "loadavg_after": os.getloadavg(),
            "protocol": args.protocol,
            "checkpoint": checkpoint_name,
            "stream": stream,
            "solver": "fmin_ncg avextol 1e-3 maxiter 100",
            "query_selection": "seed-17 sample, first "
                               f"{len(points)} of bench.py's 256",
        },
    }
    # fialint: disable=FIA502 -- pinned-baseline report: wall-clock throughput is the measurement payload
    save_json_atomic(args.out, out, indent=1)
    print(json.dumps({"scores_per_sec": out["mf"]["scores_per_sec"],
                      "queries": len(points),
                      "loadavg": load_before}))


if __name__ == "__main__":
    main()
