#!/usr/bin/env bash
# Factor-bank smoke: build a tiny bank over a synthetic split and serve
# against it in-process (fia_tpu.cli.factor --verify), asserting:
#   - the published artifact survives its own verified load
#   - banked pairs answer from the bank (hits > 0) with scores at
#     Spearman >= 0.999 vs the exact direct solver
#   - a miss falls through bitwise-identically to a bank-less engine
#     on the same solver ladder
#
#   bash scripts/factor_smoke.sh        (or: make factor-smoke)
#
# Budget: <60s on CPU — tiny synthetic splits, 300 training steps,
# embed 4 (the serve_smoke.sh shapes). The checkpoint + bank land in a
# throwaway tmpdir so repeated runs stay hermetic.
set -euo pipefail
cd "$(dirname "$0")/.."

DIR=$(mktemp -d /tmp/fia_factor_smoke.XXXXXX)
trap 'rm -rf "$DIR"' EXIT

JAX_PLATFORMS=cpu timeout -k 10 300 python -m fia_tpu.cli.factor \
  --dataset synthetic --synth_users 60 --synth_items 40 \
  --synth_train 2000 --synth_test 100 \
  --model MF --embed_size 4 --num_steps_train 300 \
  --train_dir "$DIR" \
  --bank_entries 64 --bank_top_users 12 --bank_top_items 12 \
  --bank_batch 64 --verify

echo "factor-smoke PASS"
