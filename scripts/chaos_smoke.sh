#!/usr/bin/env bash
# Chaos smoke: the fixed-seed benign schedule battery through
# fia_tpu.cli.chaos on CPU, asserting (in-process, see chaos/runner):
#   - every scenario run under a benign fault schedule reproduces its
#     undisturbed golden run bit-identically
#   - every run error is taxonomy-classified; armed faults fired
#   - damaged artifacts are detectable, quarantined, never re-read
#
#   bash scripts/chaos_smoke.sh        (or: make chaos-smoke)
#
# Budget: <60s on CPU — tiny MF workloads, shared compiled scenario
# state across runs, virtual-clock retries (no wall sleeps). Run dirs
# and repro files land in a throwaway tmpdir so repeated runs stay
# hermetic; on failure the repro JSON path is printed before cleanup.
set -euo pipefail
cd "$(dirname "$0")/.."

DIR=$(mktemp -d /tmp/fia_chaos_smoke.XXXXXX)
trap 'rm -rf "$DIR"' EXIT

# serve_stream_mesh shards dispatch over a device mesh: give the CPU
# host 8 virtual devices (same trick as tests/conftest.py) unless the
# caller already forced a device count.
if [[ "${XLA_FLAGS:-}" != *xla_force_host_platform_device_count* ]]; then
  export XLA_FLAGS="${XLA_FLAGS:-} --xla_force_host_platform_device_count=8"
fi

JAX_PLATFORMS=cpu timeout -k 10 300 python -m fia_tpu.cli.chaos \
  --smoke --workdir "$DIR"

echo "chaos-smoke PASS"
