#!/usr/bin/env python
"""Human latency/cache report over a serving metrics JSONL.

Reads the ``serve.request`` / ``serve.batch`` / ``serve.rollup`` lines
(schema: fia_tpu/serve/metrics.py) emitted by the service and prints
queue-wait and solve percentiles, cache-tier hit rates, batch shape
stats and rejection reasons.

  python scripts/latency_report.py output/serve-MF-synthetic.jsonl
"""

from __future__ import annotations

import json
import sys

import numpy as np

# What this report reads, per event — cross-checked against the
# emitted schema (fia_tpu/serve/metrics.py SCHEMA) by lint rule
# FIA401: a field renamed on the producer side fails `make lint`
# instead of rendering an empty column here. Keep it a literal dict.
CONSUMES = {
    "serve.request": ("status", "reason", "tier", "mode",
                      "queue_wait_ms", "solve_ms",
                      "approx", "err_bound", "class"),
    "serve.batch": ("size", "solve_ms"),
    "serve.rollup": ("cache",),
    # the final registry snapshot (fia_tpu/obs): per-solver-rung and
    # per-serving-mode µs histograms rendered as p50/p99 below
    "obs.metrics": ("snapshot",),
    # span stream (fia_tpu/obs/events.py): scanned for the
    # ``engine.sampled`` markers the certified sampled rung attaches
    # to its dispatch spans (queries / escalations / max bound)
    "obs.span": ("name", "events"),
    # audit subsystem (fia_tpu/audit): one line per reverse top-k
    # sweep and per live unlearning apply (docs/design.md §23)
    "audit.sweep": ("sweep_id", "test_points", "rows_scored",
                    "seconds", "rows_per_s"),
    "audit.apply": ("plan_id", "action", "status", "reason",
                    "rows_removed", "rows_reweighted", "seconds"),
}

# The canonical rejection reasons (fia_tpu/serve/admission.py). The
# histogram always prints all four, zeros included — operators diff
# these lines across runs, and a row that appears only when nonzero
# reads as "field renamed" rather than "count is zero".
CANONICAL_REASONS = ("overload", "invalid", "deadline", "degraded")

# The canonical priority classes (fia_tpu/serve/request.py), priority
# order. Same convention as the reasons above: the per-class sections
# always print all three, zeros included, so a silent class (quota'd
# out, or simply absent from the traffic mix) shows as n=0 rather
# than vanishing.
CANONICAL_CLASSES = ("interactive", "batch", "scavenger")


def pcts(vals):
    if not vals:
        return "n=0"
    a = np.asarray(vals, np.float64)
    return (f"n={len(a)}  p50={np.percentile(a, 50):.2f}ms  "
            f"p95={np.percentile(a, 95):.2f}ms  max={a.max():.2f}ms")


def load(path: str):
    reqs, batches, rollups, sampled = [], [], [], []
    sweeps, applies = [], []
    snapshot = None
    with open(path) as fh:
        for line in fh:
            line = line.strip()
            if not line:
                continue
            try:
                d = json.loads(line)
            except json.JSONDecodeError:
                continue  # torn tail line from a killed process
            ev = d.get("event")
            if ev == "serve.request":
                reqs.append(d)
            elif ev == "serve.batch":
                batches.append(d)
            elif ev == "serve.rollup":
                rollups.append(d)
            elif ev == "obs.metrics":
                snapshot = d.get("snapshot")  # last one wins
            elif ev == "obs.span":
                # the sampled rung stamps one marker per dispatch on
                # its enclosing span (engine._query_sampled)
                sampled.extend(e for e in (d.get("events") or [])
                               if e.get("name") == "engine.sampled")
            elif ev == "audit.sweep":
                sweeps.append(d)
            elif ev == "audit.apply":
                applies.append(d)
    return reqs, batches, rollups, snapshot, sampled, sweeps, applies


def hist_pct(h: dict, buckets: list, q: float) -> float:
    """Percentile (µs) from a snapshot-form fixed-bucket histogram by
    linear interpolation inside the containing bucket — the inlined
    twin of fia_tpu.obs.registry.percentile_from_snapshot (this script
    stays importable without the package on the path)."""
    count = int(h.get("count", 0))
    if count == 0:
        return 0.0
    target = q / 100.0 * count
    seen = 0
    for i, c in enumerate(h["counts"]):
        if seen + c >= target:
            if i >= len(buckets):  # +inf bucket: clamp
                return float(buckets[-1])
            lo = buckets[i - 1] if i > 0 else 0.0
            hi = buckets[i]
            frac = (target - seen) / c if c else 0.0
            return float(lo + (hi - lo) * frac)
        seen += c
    return float(buckets[-1])


def print_hist_section(title: str, snapshot: dict, prefix: str) -> None:
    """p50/p99 rows for every histogram series under ``prefix`` (e.g.
    one row per solver rung / serving mode)."""
    rows = [(k, h) for k, h in snapshot.get("histograms", {}).items()
            if k.startswith(prefix)]
    if not rows:
        return
    buckets = snapshot.get("buckets_us", [])
    print(title)
    for key, h in rows:
        label = key.split("{", 1)[1][:-1] if "{" in key else key
        p50 = hist_pct(h, buckets, 50) / 1e3
        p99 = hist_pct(h, buckets, 99) / 1e3
        print(f"  {label:<22} n={int(h['count']):<6} "
              f"p50={p50:.2f}ms  p99={p99:.2f}ms")


def print_class_hist(title: str, snapshot: dict, prefix: str) -> None:
    """p50/p99 per canonical class from the class-labelled registry
    histograms — every class prints, n=0 rows included (a class the
    quota or traffic mix silenced must read as zero, not vanish)."""
    hists = snapshot.get("histograms", {})
    buckets = snapshot.get("buckets_us", [])
    print(title)
    for cls in CANONICAL_CLASSES:
        h = hists.get(f"{prefix}{{class={cls}}}")
        if h is None:
            print(f"  class={cls:<16} n=0")
            continue
        p50 = hist_pct(h, buckets, 50) / 1e3
        p99 = hist_pct(h, buckets, 99) / 1e3
        print(f"  class={cls:<16} n={int(h['count']):<6} "
              f"p50={p50:.2f}ms  p99={p99:.2f}ms")


def print_class_report(reqs: list) -> None:
    """Per-class latency + rejection histograms from the request lines
    (multi-tenant serving). Every canonical class prints, zeros
    included; rejection rows follow the CANONICAL_REASONS convention."""
    print("classes:")
    for cls in CANONICAL_CLASSES:
        rows = [r for r in reqs if r.get("class") == cls]
        okc = [r for r in rows if r["status"] == "ok"]
        rej = [r for r in rows if r["status"] != "ok"]
        print(f"  {cls}: n={len(rows)}  ok={len(okc)}  "
              f"rejected={len(rej)}")
        if not rows:
            continue
        print(f"    queue wait: "
              f"{pcts([r['queue_wait_ms'] for r in okc])}")
        by_reason = {k: 0 for k in CANONICAL_REASONS}
        for r in rej:
            k = r.get("reason") or "<unreasoned!>"
            by_reason[k] = by_reason.get(k, 0) + 1
        for k in CANONICAL_REASONS:
            print(f"    rejected[{k}]: {by_reason[k]}")
        for k in sorted(set(by_reason) - set(CANONICAL_REASONS)):
            print(f"    rejected[{k}]: {by_reason[k]}")


def main(argv) -> int:
    if len(argv) != 2:
        print(__doc__.strip(), file=sys.stderr)
        return 2
    (reqs, batches, rollups, snapshot, sampled,
     sweeps, applies) = load(argv[1])
    if not reqs and not rollups and not sweeps and not applies:
        print(f"no serving events in {argv[1]}", file=sys.stderr)
        return 1

    ok = [r for r in reqs if r["status"] == "ok"]
    rejected = [r for r in reqs if r["status"] != "ok"]
    print(f"requests: {len(reqs)}  ok: {len(ok)}  "
          f"rejected: {len(rejected)}")

    by_reason: dict[str, int] = {r: 0 for r in CANONICAL_REASONS}
    for r in rejected:
        k = r.get("reason") or "<unreasoned!>"
        by_reason[k] = by_reason.get(k, 0) + 1
    for k in CANONICAL_REASONS:
        print(f"  rejected[{k}]: {by_reason[k]}")
    for k in sorted(set(by_reason) - set(CANONICAL_REASONS)):
        print(f"  rejected[{k}]: {by_reason[k]}")

    by_mode: dict[str, int] = {}
    for r in reqs:
        m = r.get("mode")
        if m:
            by_mode[m] = by_mode.get(m, 0) + 1
    if by_mode:
        print("modes: " + "  ".join(
            f"{k}={by_mode[k]}" for k in sorted(by_mode)))

    by_tier: dict[str, int] = {}
    for r in ok:
        t = r.get("tier") or "?"
        by_tier[t] = by_tier.get(t, 0) + 1
    served = sum(by_tier.values())
    for t in ("hot", "disk", "compute"):
        if t in by_tier:
            print(f"  tier[{t}]: {by_tier[t]} "
                  f"({100.0 * by_tier[t] / served:.1f}%)")

    # certified-approximate answer class (docs/design.md §22): answers
    # served from the sampled rung, each carrying a stamped error bound
    approx = [r for r in ok if r.get("approx")]
    if approx:
        bounds = [float(r["err_bound"]) for r in approx
                  if r.get("err_bound") is not None]
        mean_eb = f"{np.mean(bounds):.4g}" if bounds else "n/a"
        print(f"approx: {len(approx)} "
              f"({100.0 * len(approx) / len(ok):.1f}% of ok)  "
              f"mean err_bound {mean_eb}")
        print(f"  approx solve: {pcts([r['solve_ms'] for r in approx])}")
    if sampled:
        q = sum(int(e.get("queries", 0)) for e in sampled)
        esc = sum(int(e.get("escalated", 0)) for e in sampled)
        err_max = max((float(e.get("err_max", 0.0)) for e in sampled),
                      default=0.0)
        print(f"sampled rung: dispatches={len(sampled)}  queries={q}  "
              f"escalated={esc}  err_bound_max={err_max:.4g}")

    # audit subsystem (docs/design.md §23): reverse-sweep throughput
    # and live unlearning applies, from the same metrics stream
    if sweeps:
        scored = sum(int(s.get("rows_scored", 0)) for s in sweeps)
        rps = [float(s["rows_per_s"]) for s in sweeps
               if s.get("rows_per_s")]
        mean_rps = f"{np.mean(rps):,.0f}" if rps else "n/a"
        print(f"audit sweeps: {len(sweeps)}  row-scores={scored}  "
              f"mean rows/s {mean_rps}  "
              f"sweep {pcts([1e3 * float(s['seconds']) for s in sweeps])}")
    if applies:
        committed = [a for a in applies if a.get("status") == "committed"]
        rolled = [a for a in applies if a.get("status") != "committed"]
        removed = sum(int(a.get("rows_removed", 0)) for a in committed)
        rew = sum(int(a.get("rows_reweighted", 0)) for a in committed)
        print(f"audit applies: {len(applies)}  "
              f"committed={len(committed)}  rolled_back={len(rolled)}  "
              f"rows removed={removed} reweighted={rew}  "
              f"apply {pcts([1e3 * float(a['seconds']) for a in applies])}")
        for a in rolled:
            print(f"  rolled_back[{a.get('plan_id')}]: "
                  f"{a.get('reason') or '<unreasoned!>'}")

    print(f"queue wait: {pcts([r['queue_wait_ms'] for r in ok])}")
    print(f"solve:      {pcts([r['solve_ms'] for r in ok])}")

    # per-class lanes (multi-tenant serving): request lines carry a
    # "class" field since the fair-queueing scheduler landed; old logs
    # without it skip the section
    if any(r.get("class") for r in reqs):
        print_class_report(reqs)

    if batches:
        sizes = [b["size"] for b in batches]
        print(f"batches: {len(batches)}  "
              f"mean size {np.mean(sizes):.1f}  max {max(sizes)}  "
              f"dispatch {pcts([b['solve_ms'] for b in batches])}")
    if rollups:
        last = rollups[-1]
        cache = last.get("cache", {})
        if cache:
            print("cache: " + "  ".join(
                f"{k}={cache[k]}" for k in sorted(cache)))
    if snapshot:
        # registry-histogram breakdowns (fia_tpu/obs): per solver rung
        # and per serving mode, from the final obs.metrics snapshot
        print_hist_section("solve by solver rung:", snapshot,
                           "serve.solve_by_solver_us")
        print_hist_section("solve by serving mode:", snapshot,
                           "serve.solve_by_mode_us")
        print_hist_section("queue wait by mode:", snapshot,
                           "serve.queue_wait_us")
        if any(k.startswith("serve.queue_wait_by_class_us")
               for k in snapshot.get("histograms", {})):
            print_class_hist("queue wait by class:", snapshot,
                             "serve.queue_wait_by_class_us")
            print_class_hist("solve by class:", snapshot,
                             "serve.solve_by_class_us")
    return 0


if __name__ == "__main__":
    raise SystemExit(main(sys.argv))
